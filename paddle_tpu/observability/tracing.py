"""Per-request lifecycle tracing for the serving engine.

A thread-safe, ring-buffered span/event tracer: the engine records each
request's full lifecycle (queued → admitted → prefill chunk(s) →
decode/verify participation → prefix-cache hit/COW/evict → finish or
cancel) and each engine step's composition (which compiled program ran,
batch occupancy, chunk budget spent, tokens advanced per request, host
dispatch time vs the estimated device wall between dispatch-done and
token sync). Everything here is host-side bookkeeping over values the
scheduler already holds — tracing adds ZERO compiled programs and no
device traffic (pinned by test).

Correlation with the ``MetricsRegistry``: every event carries the same
``engine`` id the registry labels its serve series with, plus the
request id / step sequence number — a registry anomaly (a TTFT p99
spike at step ~N) is looked up here by ``seq``.

Exports:
  * Chrome trace-event JSON (``chrome_trace``) — loadable in Perfetto /
    ``chrome://tracing``: engine steps on tid 0, each request on its
    own tid, spans as ``ph:"X"`` complete events, instants as
    ``ph:"i"``;
  * JSON-lines (``jsonl``) — one raw event per line for grepping.

Cost model: ``PT_FLAGS_telemetry=off`` means no tracer is constructed
at all (the engine holds ``None`` — the hot path pays one identity
check, no allocation). With telemetry on, ``PT_FLAGS_trace_sample``
thins the stream deterministically: rate ``r`` records every
``round(1/r)``-th request id and step sequence number, so a sampled
request's events are complete (never a torn subset) and the ring holds
``PT_FLAGS_trace_buffer`` events at most.
"""

from __future__ import annotations

import itertools
import json
import threading
import time
import weakref
from collections import deque
from typing import List, Optional

from .. import flags

# live tracers (weak: an engine dropping its tracer drops it here too) —
# the dump CLI and the flight recorder read the process-wide view
_TRACERS: "weakref.WeakSet[Tracer]" = weakref.WeakSet()

# live fleets (EngineRouter registers itself at construction; weak so a
# dropped router drops here too) — `dump --fleet` and the merged-trace
# export read the process-wide view
_FLEETS: "weakref.WeakSet" = weakref.WeakSet()


def register_fleet(fleet):
    """Record a multi-engine front door (``inference/router.py``'s
    ``EngineRouter``) for process-wide fleet exports: ``dump --fleet``
    and :func:`fleet_chrome_trace`."""
    _FLEETS.add(fleet)


def fleets() -> List[object]:
    return list(_FLEETS)


def sample_period(rate: float) -> int:
    """rate → keep-every-Nth period: 1.0 → 1, 0.5 → 2, 0.1 → 10."""
    if rate >= 1.0:
        return 1
    return max(1, int(round(1.0 / max(float(rate), 1e-9))))


class Tracer:
    """Ring-buffered lifecycle tracer for one engine.

    Events are plain dicts of JSON-serializable host values:
    ``{"kind": "step"|"request"|"engine", "name", "t0", "t1"|None,
    "engine", "rid"|"seq", "args": {...}}``. Times are
    ``time.perf_counter()`` seconds (monotonic; ``epoch_unix`` anchors
    them to wall clock for log correlation). ``t1 is None`` marks an
    instant event; otherwise [t0, t1] is a span.
    """

    def __init__(self, engine_id: str = "0",
                 capacity: Optional[int] = None,
                 sample: Optional[float] = None):
        if capacity is None:
            capacity = int(flags.flag("trace_buffer"))
        if sample is None:
            sample = float(flags.flag("trace_sample"))
        self.engine_id = str(engine_id)
        self.period = sample_period(sample)
        self._buf: deque = deque(maxlen=max(int(capacity), 1))
        self._lock = threading.Lock()
        self._seq = itertools.count(1)
        self._eng_n = itertools.count()
        self.epoch_unix = time.time()
        self.epoch_perf = time.perf_counter()
        _TRACERS.add(self)

    # ---------------- sampling ----------------
    def want_request(self, rid: int) -> bool:
        return rid % self.period == 0

    def next_step(self) -> int:
        """Monotonic step sequence number (always advances, sampled or
        not, so ``seq`` stays a stable correlation key)."""
        return next(self._seq)

    def want_step(self, seq: int) -> bool:
        return seq % self.period == 0

    # ---------------- writes ----------------
    def _push(self, ev: dict):
        with self._lock:
            self._buf.append(ev)

    def step(self, seq: int, program: str, t0: float, t1: float, **args):
        """One engine step's composition: ``program`` is the compiled
        program that ran (prefill_chunk / prefill_bucket / decode /
        decode_chunk / verify); args carry occupancy, budget, per-rid
        tokens advanced, dispatch vs sync wall."""
        self._push({"kind": "step", "seq": seq, "name": program,
                    "t0": t0, "t1": t1, "engine": self.engine_id,
                    "args": args})

    def request(self, rid: int, name: str, t0: Optional[float] = None,
                t1: Optional[float] = None, **args):
        """A request lifecycle event: instant (``t1=None``) or span."""
        if t0 is None:
            t0 = time.perf_counter()
        self._push({"kind": "request", "rid": int(rid), "name": name,
                    "t0": t0, "t1": t1, "engine": self.engine_id,
                    "args": args})

    def engine_event(self, name: str, _force: bool = False, **args):
        """Engine-scoped instant (e.g. a prefix-cache eviction storm).
        Rate-gated by the same sample period as requests/steps: an
        unsampled flood of COW/evict instants must not cycle the ring
        and evict the rare request spans a low ``trace_sample`` was
        set to preserve. ``_force`` bypasses the thinning for rare
        MUST-RECORD events (alert transitions): dropping one of those
        to rate-gating would hide the incident the tracer exists to
        explain."""
        if not _force and next(self._eng_n) % self.period != 0:
            return
        self._push({"kind": "engine", "name": name,
                    "t0": time.perf_counter(), "t1": None,
                    "engine": self.engine_id, "args": args})

    # ---------------- reads ----------------
    def events(self) -> List[dict]:
        """Snapshot copy, oldest first."""
        with self._lock:
            return list(self._buf)

    def recent(self, n: int) -> List[dict]:
        with self._lock:
            k = len(self._buf)
            return list(itertools.islice(self._buf, max(k - n, 0), k))

    def __len__(self):
        return len(self._buf)


# ---------------------------------------------------------------------------
# exports
# ---------------------------------------------------------------------------
def all_tracers() -> List[Tracer]:
    return list(_TRACERS)


def recent_events(n: int = 64) -> List[dict]:
    """Last ``n`` events across every live tracer, oldest first — what
    the flight recorder attaches to an anomaly dump."""
    evs: List[dict] = []
    for tr in all_tracers():
        evs.extend(tr.recent(n))
    evs.sort(key=lambda e: e["t0"])
    return evs[-n:]


def _pid(tr: Tracer) -> int:
    eid = tr.engine_id
    return int(eid) + 1 if eid.isdigit() else (abs(hash(eid)) % 9973) + 1


def chrome_events(tracers: Optional[List[Tracer]] = None) -> List[dict]:
    """Flatten tracer rings into Chrome trace-event dicts (``ts``/
    ``dur`` in microseconds; engine steps on tid 0, request rid r on
    tid r+1 — tid 0 is reserved so a request id of 0 cannot collide
    with the step track)."""
    if tracers is None:
        tracers = all_tracers()
    out: List[dict] = []
    for tr in tracers:
        pid = _pid(tr)
        out.append({"name": "process_name", "ph": "M", "pid": pid,
                    "tid": 0,
                    "args": {"name": f"paddle_tpu engine {tr.engine_id}"}})
        out.append({"name": "thread_name", "ph": "M", "pid": pid,
                    "tid": 0, "args": {"name": "engine steps"}})
        named_tids = set()
        for ev in tr.events():
            if ev["kind"] == "step":
                tid = 0
                args = dict(ev["args"], seq=ev["seq"])
            elif ev["kind"] == "request":
                tid = ev["rid"] + 1
                args = dict(ev["args"], rid=ev["rid"])
                if tid not in named_tids:
                    named_tids.add(tid)
                    out.append({"name": "thread_name", "ph": "M",
                                "pid": pid, "tid": tid,
                                "args": {"name": f"request {ev['rid']}"}})
            else:
                tid = 0
                args = dict(ev["args"])
            ts = ev["t0"] * 1e6
            if ev["t1"] is not None:
                out.append({"name": ev["name"], "ph": "X", "ts": ts,
                            "dur": max((ev["t1"] - ev["t0"]) * 1e6, 0.0),
                            "pid": pid, "tid": tid, "cat": ev["kind"],
                            "args": args})
            else:
                out.append({"name": ev["name"], "ph": "i", "ts": ts,
                            "s": "t", "pid": pid, "tid": tid,
                            "cat": ev["kind"], "args": args})
    out.sort(key=lambda e: e.get("ts", 0.0))
    return out


def chrome_trace(tracers: Optional[List[Tracer]] = None) -> dict:
    """Perfetto/chrome://tracing-loadable document."""
    return {"traceEvents": chrome_events(tracers),
            "displayTimeUnit": "ms"}


def _rid_hops(tracers: List[Tracer]):
    """Per-tracer per-rid request activity: ``[(tracer, {rid: {first,
    last, spans}})]`` — the raw material for cross-replica flow
    correlation. Spans are (t0, t1) pairs; instants only move the
    first/last stamps."""
    per = []
    for tr in tracers:
        rids: dict = {}
        for ev in tr.events():
            if ev["kind"] != "request":
                continue
            d = rids.setdefault(
                ev["rid"], {"first": ev["t0"], "last": ev["t0"],
                            "spans": []})
            d["first"] = min(d["first"], ev["t0"])
            d["last"] = max(d["last"], ev["t0"])
            if ev["t1"] is not None:
                d["spans"].append((ev["t0"], ev["t1"]))
        per.append((tr, rids))
    return per


def _flow_anchor(d: dict, last: bool):
    """(ts_us inside an X slice, synthesized_event_or_None) for one
    hop end. Flow events bind to the slice ENCLOSING their ts on that
    pid/tid, so when the hop's rid has no span there (all instants — a
    reclaimed victim that re-queued but never finished, say) a 1 µs
    ``handoff`` slice is synthesized at the boundary instant."""
    if d["spans"]:
        spans = sorted(d["spans"])
        t0, t1 = spans[-1] if last else spans[0]
        return (t0 + max(t1 - t0, 0) / 2) * 1e6, None
    t = (d["last"] if last else d["first"]) * 1e6
    return t + 0.5, {"name": "handoff", "ph": "X", "ts": t, "dur": 1.0,
                     "cat": "request"}


def fleet_flow_events(tracers: List[Tracer]) -> List[dict]:
    """Chrome flow events (``ph`` ``s``/``f``, ``id`` = rid) joining a
    request's spans across every tracer it visited — the line Perfetto
    draws from a failed-over rid's life on the dead replica to its
    replayed life on the survivor. Consecutive hops are ordered by the
    rid's first event time per tracer."""
    per = _rid_hops(tracers)
    all_rids = set()
    for _tr, rids in per:
        all_rids.update(rids)
    out: List[dict] = []
    # a span-less MIDDLE hop of a 3+ hop chain anchors both its
    # incoming flow finish and its outgoing flow start — synthesize
    # its handoff slice once, not per adjacent pair
    seen_syn = set()
    for rid in sorted(all_rids):
        hops = sorted(
            ((d[rid]["first"], tr, d[rid]) for tr, d in per
             if rid in d), key=lambda h: h[0])
        if len(hops) < 2:
            continue
        for (_, tr_a, d_a), (_, tr_b, d_b) in zip(hops, hops[1:]):
            ts_a, syn_a = _flow_anchor(d_a, last=True)
            ts_b, syn_b = _flow_anchor(d_b, last=False)
            pid_a, pid_b = _pid(tr_a), _pid(tr_b)
            tid = rid + 1
            for syn, pid in ((syn_a, pid_a), (syn_b, pid_b)):
                if syn is not None:
                    key = (pid, tid, syn["ts"])
                    if key not in seen_syn:
                        seen_syn.add(key)
                        out.append(dict(syn, pid=pid, tid=tid,
                                        args={"rid": rid}))
            flow = {"name": f"rid {rid}", "cat": "failover",
                    "id": int(rid), "tid": tid}
            out.append(dict(flow, ph="s", ts=ts_a, pid=pid_a))
            # bp:"e" binds the finish to its ENCLOSING slice (the
            # replayed life's first span), not the next slice to start
            out.append(dict(flow, ph="f", bp="e", ts=ts_b, pid=pid_b))
    return out


def fleet_chrome_trace(fleet=None) -> dict:
    """ONE Perfetto-loadable document for a whole fleet: the router's
    tracer and every replica engine's tracer merged, with a
    failed-over rid's spans appearing on BOTH replicas' request tracks
    joined by flow events (:func:`fleet_flow_events`). ``fleet`` is an
    ``EngineRouter`` (duck-typed: ``_tracer`` + ``_replicas``); None
    merges every live tracer in the process — the ``dump --fleet`` /
    ``/trace?fleet=1`` export path."""
    if fleet is None:
        tracers = all_tracers()
        # deterministic merge order regardless of weakset iteration
        tracers.sort(key=lambda t: t.engine_id)
        flow_from = tracers
    else:
        tracers = []
        rt = getattr(fleet, "_tracer", None)
        if rt is not None:
            tracers.append(rt)
        flow_from = []
        for rep in list(getattr(fleet, "_replicas", ())):
            tr = getattr(rep.engine, "_tracer", None)
            if tr is not None:
                tracers.append(tr)
                flow_from.append(tr)
    events = chrome_events(tracers)
    events.extend(fleet_flow_events(flow_from))
    events.sort(key=lambda e: e.get("ts", 0.0))
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def jsonl(tracers: Optional[List[Tracer]] = None) -> str:
    """Raw events, one JSON object per line, oldest first."""
    if tracers is None:
        tracers = all_tracers()
    evs: List[dict] = []
    for tr in tracers:
        evs.extend(tr.events())
    evs.sort(key=lambda e: e["t0"])
    return "\n".join(json.dumps(e, default=str) for e in evs)
