"""Trainer telemetry: per-step instrumentation for ``TrainStep.run``.

Sampling discipline (the acceptance-critical part): every step records
only host-side wall time into the ring buffer — cheap python, no device
traffic. On a sample-every-N cadence the loss / grad-norm device
scalars (which the compiled step already produced) are fetched, gauges
update, ``device.memory_stats()`` is read, and the anomaly watchdog
runs. Non-sampled steps perform NO ``device_get``/host sync beyond what
the caller does with the returned loss.

Rate metrics (tokens/s, MFU) are averaged over the SAMPLING INTERVAL,
measured between post-fetch sync points: per-step wall clock only times
the async *dispatch*, which can run orders of magnitude ahead of the
device and would report impossible throughput (MFU > 1). The interval
endpoints sit right after ``float(loss)`` — a real completion fence —
so the rate is device-true in steady state. The first interval includes
compile time and undershoots; that is the honest direction.
"""

from __future__ import annotations

import time
from typing import Callable, Optional

from .. import flags
from .recorder import AnomalyWatchdog, FlightRecorder
from .registry import exp_buckets, get_registry

# device_kind -> peak bf16 FLOP/s per chip (public spec sheets); the
# MFU estimate is best-effort — unknown kinds (CPU CI) report no MFU
_PEAK_BF16_FLOPS = {
    "TPU v4": 275e12,
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v6e": 918e12,
    "TPU v6 lite": 918e12,
}


def _peak_flops() -> Optional[float]:
    import jax

    kind = getattr(jax.devices()[0], "device_kind", "")
    for k, v in _PEAK_BF16_FLOPS.items():
        if k.lower() in str(kind).lower():
            return v
    return None


def _memory_stats() -> Optional[dict]:
    import jax

    try:
        stats = jax.devices()[0].memory_stats()
    except Exception:
        return None
    if not stats:
        return None
    return {k: stats[k] for k in ("bytes_in_use", "peak_bytes_in_use")
            if k in stats}


class TrainTelemetry:
    """One instance per TrainStep; holds its metrics, flight recorder
    and watchdog. Construct only when telemetry is enabled — callers
    keep ``None`` otherwise so the off path is a single identity
    check."""

    def __init__(self, sample_every: Optional[int] = None,
                 flight_window: Optional[int] = None,
                 dump_dir: Optional[str] = None,
                 spike_factor: Optional[float] = None):
        self.sample_every = max(1, int(
            sample_every if sample_every is not None
            else flags.flag("telemetry_sample_every")))
        reg = get_registry()
        self.recorder = FlightRecorder(
            capacity=(flight_window if flight_window is not None
                      else flags.flag("telemetry_flight_window")),
            dump_dir=(dump_dir if dump_dir is not None
                      else flags.flag("telemetry_dump_dir")))
        self.watchdog = AnomalyWatchdog(
            self.recorder,
            spike_factor=(spike_factor if spike_factor is not None
                          else flags.flag("telemetry_grad_spike_factor")))
        self._steps = reg.counter(
            "pt_train_steps_total", "optimizer steps executed")
        self._tokens = reg.counter(
            "pt_train_tokens_total", "tokens consumed by training")
        self._step_ms = reg.histogram(
            "pt_train_step_ms", "host wall-clock per train step (ms)",
            buckets=exp_buckets(0.5, 2.0, 20))
        self._loss = reg.gauge("pt_train_loss", "last sampled loss")
        self._gnorm = reg.gauge(
            "pt_train_grad_norm", "last sampled global gradient norm")
        self._tps = reg.gauge(
            "pt_train_tokens_per_sec", "sampled-step token throughput")
        self._mfu = reg.gauge(
            "pt_train_mfu", "estimated model FLOPs utilization (0-1)")
        self._mem = reg.gauge(
            "pt_device_memory_bytes", "device memory_stats()",
            labels=("stat",))
        self._flops_per_step: Optional[float] = None
        self._flops_known = False
        self._peak = None
        self._peak_known = False
        # sampling-interval accumulators (rates are computed between
        # post-fetch sync points, not from per-step dispatch wall time)
        self._interval_t0 = time.perf_counter()
        self._interval_tokens = 0
        self._interval_steps = 0
        self.samples = 0
        self.last_sample: dict = {}

    # ------------------------------------------------------------------
    def should_sample(self, step: int) -> bool:
        return step % self.sample_every == 0

    def on_step(self, step: int, loss, grad_norm, tokens: int,
                wall_s: float,
                flops_getter: Optional[Callable[[], Optional[float]]] = None):
        """``loss``/``grad_norm`` are device scalars (async futures) —
        they are fetched ONLY on sampled steps."""
        wall_ms = wall_s * 1e3
        self._steps.inc()
        if tokens:
            self._tokens.inc(tokens)
        self._step_ms.observe(wall_ms)
        self._interval_tokens += int(tokens)
        self._interval_steps += 1
        rec = {"step": step, "wall_ms": round(wall_ms, 3),
               "tokens": int(tokens)}
        if not self.should_sample(step):
            self.recorder.record(**rec)
            return None
        # ---- sampled step: host sync on the two scalars ----
        loss_f = float(loss) if loss is not None else None
        gnorm_f = float(grad_norm) if grad_norm is not None else None
        # the float() above fenced this step's completion: NOW is a
        # device-true interval endpoint for the rate metrics
        now = time.perf_counter()
        interval_s = now - self._interval_t0
        if loss_f is not None:
            self._loss.set(loss_f)
            rec["loss"] = loss_f
        if gnorm_f is not None:
            self._gnorm.set(gnorm_f)
            rec["grad_norm"] = gnorm_f
        if self._interval_tokens and interval_s > 0:
            tps = self._interval_tokens / interval_s
            self._tps.set(tps)
            rec["tokens_per_sec"] = round(tps, 1)
        mfu = self._mfu_estimate(
            interval_s / max(self._interval_steps, 1), flops_getter)
        if mfu is not None:
            self._mfu.set(mfu)
            rec["mfu_est"] = round(mfu, 4)
        self._interval_t0 = now
        self._interval_tokens = 0
        self._interval_steps = 0
        if flags.flag("log_memory_stats"):
            mem = _memory_stats()
            if mem:
                for k, v in mem.items():
                    self._mem.set(v, stat=k)
                rec["memory"] = mem
        self.recorder.record(**rec)
        self.samples += 1
        self.last_sample = rec
        return self.watchdog.check(step, loss_f, gnorm_f)

    def _mfu_estimate(self, wall_s: float, flops_getter) -> Optional[float]:
        # peak first: on devices with no spec-sheet entry (CPU CI) MFU
        # is undefined, so never pay the FLOPs probe (an AOT
        # lower+compile) there
        if not self._peak_known:
            self._peak_known = True
            try:
                self._peak = _peak_flops()
            except Exception:
                self._peak = None
        if not self._peak:
            return None
        if not self._flops_known:
            self._flops_known = True
            if flops_getter is not None:
                try:
                    self._flops_per_step = flops_getter()
                except Exception:
                    self._flops_per_step = None
        if not self._flops_per_step or wall_s <= 0:
            return None
        return self._flops_per_step / wall_s / self._peak


def record_scalars(prefix: str, logs: Optional[dict], step=None):
    """Publish a dict of scalar logs as ``pt_<prefix>_<key>`` gauges —
    the shared funnel the hapi callbacks (ProgBarLogger / VisualDL /
    MetricsLogger) emit through. Non-numeric values are skipped."""
    if not logs:
        return
    reg = get_registry()
    for k, v in logs.items():
        try:
            f = float(v[0] if isinstance(v, (list, tuple)) else v)
        except (TypeError, ValueError, IndexError):
            continue
        name = "pt_" + "".join(
            c if c.isalnum() or c == "_" else "_"
            for c in f"{prefix}_{k}".lower())
        reg.gauge(name, f"hapi scalar {prefix}/{k}").set(f)
