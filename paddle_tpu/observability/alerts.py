"""Rule-based alerting over the serving time-series history.

Detectors evaluate the :class:`~.timeseries.TimeSeriesStore` windows
once per closed window, entirely on tick-derived data — same
determinism contract as the store itself, so a seeded fault storm
fires the same alerts at the same ticks every run (pinned by test).

Every rule carries HYSTERESIS: it must observe ``fire_for`` consecutive
bad windows before firing and ``clear_for`` consecutive healthy windows
before clearing, so a metric oscillating around a threshold can never
flap the alert. A firing transition:

* increments ``pt_serve_alerts_fired_total{engine,rule}`` and sets the
  ``pt_serve_alert_active{engine,rule}`` gauge;
* emits a structured ``alert`` tracer event (``alert_clear`` on the way
  back) — forced past the tracer's sample thinning, an alert is never
  dropped by rate-gating;
* (telemetry on) dumps a FlightRecorder artifact carrying the
  TRIGGERING WINDOW of series samples — the postmortem shows the burn
  building, not just that it fired.

``ALERT_RULES`` is the canonical rule registry ptlint's OBS002 checks
for completeness (every implemented rule must appear here AND in the
README alerts table, the FL003 shape); :class:`AlertManager` enforces
the same at runtime.

The read-only hook the degradation ladder consumes
(``PT_FLAGS_slo_degradation``, default off): the engine's health tick
reads :meth:`AlertManager.is_active`\\("slo_burn_rate") and treats an
active burn as saturation pressure — capacity rungs only (shed batch /
throttle), never the fault jump; with the flag off the ladder's inputs
are untouched and outputs are pinned identical.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from .. import flags
from .registry import get_registry

# ---------------------------------------------------------------------------
# the canonical rule registry (ptlint OBS002: every AlertRule
# implementation's ``name`` must appear here and in the README alerts
# table — a detector cannot ship invisibly to the operator surface)
# ---------------------------------------------------------------------------
ALERT_RULES: Dict[str, str] = {
    "slo_burn_rate": "multi-window SLO burn: TTFT/TPOT attainment "
                     "violations per class are eating error budget at "
                     ">= threshold x in BOTH the fast and slow windows",
    "queue_depth_growth": "admission queue depth grew monotonically "
                          "across the last windows and sits above the "
                          "floor — demand is outrunning service",
    "prefix_hit_collapse": "prefix-cache token hit-rate collapsed "
                           "below the floor after a healthy baseline "
                           "(eviction storm / working-set shift)",
    "spec_accept_collapse": "speculative-decode acceptance collapsed "
                            "below the floor after a healthy baseline "
                            "— verify passes are burning weight "
                            "streams for nothing",
    "recompile_post_seal": "a compiled serving program re-specialized "
                           "after the recompile watchdog sealed the "
                           "program set",
    "hbm_residency": "KV pool residency is pinned against pool "
                     "capacity — admission is about to block on pages",
}


class AlertRule:
    """Base detector: subclasses implement :meth:`check` over the
    store's sample list; :meth:`update` wraps it in the hysteresis
    state machine shared by every rule."""

    name = ""

    def __init__(self, fire_for: int = 2, clear_for: int = 3):
        if int(fire_for) < 1 or int(clear_for) < 1:
            raise ValueError(
                f"fire_for/clear_for must be >= 1; got "
                f"({fire_for}, {clear_for})")
        self.fire_for = int(fire_for)
        self.clear_for = int(clear_for)
        # trailing samples check() actually reads — the manager hands
        # every rule max(window_need) samples instead of copying the
        # whole retained ring each window
        self.window_need = 1
        self.active = False
        self.fired = 0
        self.value: Optional[float] = None  # last computed scalar
        self.peak = 0.0  # max scalar this measurement window
        self.detail: dict = {}
        self._bad_streak = 0
        self._good_streak = 0

    # -- subclass contract --
    def check(self, samples: List[dict]) -> Tuple[bool, dict]:
        """(condition_bad, detail) for the CURRENT window; ``detail``
        should carry a ``"value"`` scalar (the rule's headline
        number)."""
        raise NotImplementedError

    # -- hysteresis --
    def update(self, samples: List[dict]) -> Optional[str]:
        """One closed window: returns ``"fire"`` / ``"clear"`` on a
        state transition, else None."""
        if not samples:
            return None
        bad, detail = self.check(samples)
        self.detail = detail
        v = detail.get("value")
        if isinstance(v, (int, float)):
            self.value = float(v)
            if self.value > self.peak:
                self.peak = self.value
        if bad:
            self._bad_streak += 1
            self._good_streak = 0
            if not self.active and self._bad_streak >= self.fire_for:
                self.active = True
                self.fired += 1
                return "fire"
        else:
            self._good_streak += 1
            self._bad_streak = 0
            if self.active and self._good_streak >= self.clear_for:
                self.active = False
                return "clear"
        return None


def _sum_deltas(samples: List[dict], key: str) -> float:
    return sum(s["deltas"].get(key, 0.0) for s in samples)


class SLOBurnRate(AlertRule):
    """Multi-window burn-rate over TTFT/TPOT attainment: per SLO class,
    ``burn = (violated / tracked) / budget`` aggregated over a FAST and
    a SLOW window; the rule is bad when any class with enough tracked
    finishes burns >= ``threshold`` in BOTH windows (the classic
    fast-and-slow pairing: the slow window proves it's sustained, the
    fast window proves it's still happening)."""

    name = "slo_burn_rate"

    def __init__(self, budget: float = 0.1, threshold: float = 2.0,
                 fast_windows: int = 1, slow_windows: int = 4,
                 min_tracked: int = 2, **kw):
        super().__init__(**kw)
        if not 0 < budget <= 1:
            raise ValueError(f"budget must be in (0, 1]; got {budget}")
        self.budget = float(budget)
        self.threshold = float(threshold)
        self.fast_windows = max(int(fast_windows), 1)
        self.slow_windows = max(int(slow_windows), self.fast_windows)
        self.min_tracked = max(int(min_tracked), 1)
        self.window_need = self.slow_windows

    @staticmethod
    def _burns(samples, budget):
        agg: Dict[str, float] = {}
        for s in samples:
            for k, d in s["deltas"].items():
                if k.startswith(("slo_met:", "slo_violated:")):
                    agg[k] = agg.get(k, 0.0) + d
        out = {}
        for k in agg:
            if not k.startswith("slo_met:"):
                continue
            cls = k.split(":", 1)[1]
            met = agg.get(f"slo_met:{cls}", 0.0)
            vio = agg.get(f"slo_violated:{cls}", 0.0)
            tracked = met + vio
            if tracked > 0:
                out[cls] = ((vio / tracked) / budget, tracked)
        return out

    def check(self, samples):
        fast = self._burns(samples[-self.fast_windows:], self.budget)
        slow = self._burns(samples[-self.slow_windows:], self.budget)
        worst, worst_cls = 0.0, None
        for cls, (b_slow, tracked) in slow.items():
            if tracked < self.min_tracked or cls not in fast:
                continue
            b = min(fast[cls][0], b_slow)  # BOTH windows must burn
            if b > worst:
                worst, worst_cls = b, cls
        return worst >= self.threshold, {
            "value": round(worst, 4), "slo": worst_cls,
            "budget": self.budget, "threshold": self.threshold}


class QueueDepthGrowth(AlertRule):
    """Queue depth grew strictly across the last ``windows`` samples
    and ends >= ``min_depth`` — sustained demand the engine is not
    absorbing (the time-series view of saturation, vs backpressure()'s
    instantaneous verdict)."""

    name = "queue_depth_growth"

    def __init__(self, windows: int = 3, min_depth: int = 2, **kw):
        super().__init__(**kw)
        self.windows = max(int(windows), 2)
        self.min_depth = int(min_depth)
        self.window_need = self.windows

    def check(self, samples):
        win = samples[-self.windows:]
        depths = [s["gauges"].get("queue_depth", 0.0) for s in win]
        growing = (len(win) >= self.windows
                   and all(b > a for a, b in zip(depths, depths[1:]))
                   and depths[-1] >= self.min_depth)
        return growing, {"value": depths[-1] if depths else 0.0,
                         "depths": depths}


class _RatioCollapse(AlertRule):
    """Shared shape for hit-rate / acceptance collapse: the CURRENT
    window's ratio fell below ``floor`` while the BASELINE windows
    (the preceding ones) were healthy (>= ``healthy``) — a rule that
    only ever knew a cold cache must not page anyone."""

    _num = ""
    _den = ""

    def __init__(self, floor: float = 0.2, healthy: float = 0.4,
                 baseline_windows: int = 4, min_den: float = 4.0, **kw):
        super().__init__(**kw)
        self.floor = float(floor)
        self.healthy = float(healthy)
        self.baseline_windows = max(int(baseline_windows), 1)
        self.min_den = float(min_den)
        self.window_need = self.baseline_windows + 1

    def _ratio(self, samples):
        num = _sum_deltas(samples, self._num)
        den = _sum_deltas(samples, self._den)
        return (num / den if den > 0 else None), den

    def check(self, samples):
        cur, den = self._ratio(samples[-1:])
        base, base_den = self._ratio(
            samples[-1 - self.baseline_windows:-1])
        bad = (cur is not None and den >= self.min_den
               and cur < self.floor
               and base is not None and base_den >= self.min_den
               and base >= self.healthy)
        return bad, {"value": (round(cur, 4) if cur is not None
                               else None),
                     "baseline": (round(base, 4) if base is not None
                                  else None),
                     "floor": self.floor}


class PrefixHitCollapse(_RatioCollapse):
    name = "prefix_hit_collapse"
    _num = "prefix_hit_tokens"
    _den = "prefix_prompt_tokens"


class SpecAcceptCollapse(_RatioCollapse):
    name = "spec_accept_collapse"
    _num = "spec_accepted"
    _den = "spec_proposed"

    def __init__(self, floor: float = 0.15, healthy: float = 0.3,
                 **kw):
        super().__init__(floor=floor, healthy=healthy, **kw)


class RecompilePostSeal(AlertRule):
    """Any post-seal recompile counted by the watchdog inside the
    window is an incident on its own — ``fire_for`` defaults to 1
    (hysteresis still prevents re-firing while it stays active)."""

    name = "recompile_post_seal"

    def __init__(self, fire_for: int = 1, **kw):
        super().__init__(fire_for=fire_for, **kw)

    def check(self, samples):
        d = samples[-1]["deltas"].get("recompiles", 0.0)
        return d > 0, {"value": d}


class HbmResidency(AlertRule):
    """KV pool residency vs pool capacity: utilization pinned at
    >= ``threshold`` — the next admission wave blocks on pages."""

    name = "hbm_residency"

    def __init__(self, threshold: float = 0.97, **kw):
        super().__init__(**kw)
        self.threshold = float(threshold)

    def check(self, samples):
        util = samples[-1]["gauges"].get("kv_utilization", 0.0)
        return util >= self.threshold, {
            "value": round(util, 4), "threshold": self.threshold}


def default_rules() -> List[AlertRule]:
    """One instance of every registered rule, default tuning."""
    return [SLOBurnRate(), QueueDepthGrowth(), PrefixHitCollapse(),
            SpecAcceptCollapse(), RecompilePostSeal(), HbmResidency()]


class AlertManager:
    """Per-engine detector set evaluated once per closed time-series
    window (the engine calls :meth:`evaluate` from its scheduler tick
    — single-threaded writes; :meth:`snapshot` is copy-on-read for the
    scrape thread, the SAFE_READS contract)."""

    def __init__(self, label: str = "0",
                 rules: Optional[List[AlertRule]] = None,
                 tracer=None):
        self.label = str(label)
        self._rules = list(rules) if rules is not None \
            else default_rules()
        seen = set()
        for r in self._rules:
            if r.name not in ALERT_RULES:
                raise ValueError(
                    f"unknown alert rule {r.name!r} — register it in "
                    "observability.alerts.ALERT_RULES (ptlint OBS002 "
                    "keeps this registry complete)")
            if r.name in seen:
                raise ValueError(f"duplicate alert rule {r.name!r}")
            seen.add(r.name)
        self._window_need = max(
            (r.window_need for r in self._rules), default=1)
        self._tracer = tracer
        self._recorder = None
        reg = get_registry()
        L = ("engine", "rule")
        self._fired_c = reg.counter(
            "pt_serve_alerts_fired_total",
            "alert-rule firing transitions (hysteresis-gated: "
            "fire_for consecutive bad windows to fire, clear_for "
            "healthy ones to clear — no flapping)", L)
        self._active_g = reg.gauge(
            "pt_serve_alert_active",
            "1 while the alert rule is in its fired state", L)
        # host counters (available with telemetry off, like every
        # other serving stat surface)
        self.alert_stats = {"evaluated": 0, "fired": 0, "cleared": 0}
        # bounded transition log — a plain list (list() copies are
        # GIL-atomic for the scrape thread, the DegradationController
        # pattern), trimmed to the cap on append
        self.transitions: list = []
        self._max_transitions = 128

    # ---------------- evaluation (scheduler thread) ----------------
    def evaluate(self, store) -> List[dict]:
        """Run every rule over the store's trailing windows (only as
        many as the widest rule reads — not the whole retained ring);
        returns the transitions this window produced (usually [])."""
        samples = store.last(self._window_need)
        if not samples:
            return []
        self.alert_stats["evaluated"] += 1
        out: List[dict] = []
        for rule in self._rules:
            tr = rule.update(samples)
            if tr is None:
                continue
            lab = {"engine": self.label, "rule": rule.name}
            if tr == "fire":
                self.alert_stats["fired"] += 1
                self._fired_c.inc(**lab)
                self._active_g.set(1, **lab)
                self._artifact(rule, samples)
            else:
                self.alert_stats["cleared"] += 1
                self._active_g.set(0, **lab)
            if self._tracer is not None:
                # _force: an alert transition must never be dropped by
                # the tracer's deterministic sample thinning
                self._tracer.engine_event(
                    "alert" if tr == "fire" else "alert_clear",
                    _force=True, rule=rule.name,
                    detail=dict(rule.detail))
            ev = {"rule": rule.name, "event": tr,
                  "tick": samples[-1]["tick"],
                  "detail": dict(rule.detail)}
            self.transitions.append(ev)
            if len(self.transitions) > self._max_transitions:
                del self.transitions[
                    :len(self.transitions) - self._max_transitions]
            out.append(ev)
        return out

    def _artifact(self, rule: AlertRule, samples: List[dict]):
        """FlightRecorder postmortem for a firing: the rule, its
        detail, and the TRIGGERING WINDOW of series samples. Telemetry
        off = host counters only (the NaN-dump / watchdog gate)."""
        from .registry import enabled

        if not enabled():
            return
        if self._recorder is None:
            from .recorder import FlightRecorder

            self._recorder = FlightRecorder(
                capacity=int(flags.flag("telemetry_flight_window")),
                dump_dir=str(flags.flag("telemetry_dump_dir")))
        self._recorder.record(
            kind="alert", rule=rule.name, engine=self.label,
            detail=dict(rule.detail), window=samples[-8:])
        self._recorder.dump(
            f"serving alert {rule.name!r} fired (engine "
            f"{self.label}) — triggering series window attached")

    # ---------------- read side ----------------
    def is_active(self, name: str) -> bool:
        """Read-only signal hook (documented consumer: the degradation
        ladder under ``PT_FLAGS_slo_degradation``). Never mutates rule
        state — safe to poll every tick."""
        return any(r.active for r in self._rules if r.name == name)

    def snapshot(self) -> dict:
        """Copy-on-read view for the scrape thread: per-rule state,
        the active set, cumulative fire counts and the bounded
        transition log."""
        rules = {}
        for r in list(self._rules):
            rules[r.name] = {
                "active": r.active,
                "fired": r.fired,
                "value": r.value,
                "peak": r.peak,
                "detail": {k: v for k, v in list(r.detail.items())},
            }
        st = {k: v for k, v in list(self.alert_stats.items())}
        return {
            "label": self.label,
            "rules": rules,
            "active": sorted(n for n, d in rules.items()
                             if d["active"]),
            "fired_total": sum(d["fired"] for d in rules.values()),
            "stats": st,
            "transitions": list(self.transitions),
        }

    def window_reset(self):
        """Zero the per-rule peak trackers — one measurement window
        per bench sweep step (fire counts, hysteresis state and the
        registry totals keep running, the metrics_window_reset
        contract)."""
        for r in self._rules:
            r.peak = 0.0
