"""paddle.hub namespace (parity: python/paddle/hashub.py — hubconf.py
loading). Network sources (github/gitee) are unreachable from a
zero-egress TPU pod; LOCAL hub repos — a directory with hubconf.py —
work exactly like upstream's source='local' mode, which is also what
air-gapped paddle deployments use.
"""

from __future__ import annotations

import importlib.util
import os

_ENTRY_PREFIX = "_"  # hubconf entries are public callables
_cache = {}


def _load_hubconf(repo_dir: str, force_reload: bool = False):
    """Executed once per repo_dir (hubconf module-level side effects —
    weight loads, registries — must not repeat for list+load
    sequences); force_reload re-executes."""
    key = os.path.abspath(repo_dir)
    if not force_reload and key in _cache:
        return _cache[key]
    path = os.path.join(repo_dir, "hubconf.py")
    if not os.path.isfile(path):
        raise FileNotFoundError(f"no hubconf.py under {repo_dir!r}")
    spec = importlib.util.spec_from_file_location("hubconf", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    _cache[key] = mod
    return mod


def _require_local(source):
    if source not in ("local",):
        raise NotImplementedError(
            "paddle_tpu.hub reaches no network (zero-egress TPU pod): "
            "clone the repo and use source='local' with its path, "
            "matching upstream's local mode")


def list(repo_dir, source="local", force_reload=False):  # noqa: A001
    _require_local(source)
    mod = _load_hubconf(repo_dir, force_reload)
    return [n for n in dir(mod)
            if callable(getattr(mod, n)) and not n.startswith(_ENTRY_PREFIX)]


def help(repo_dir, model, source="local", force_reload=False):  # noqa: A001
    _require_local(source)
    return getattr(_load_hubconf(repo_dir, force_reload), model).__doc__


def load(repo_dir, model, *args, source="local", force_reload=False,
         **kwargs):
    _require_local(source)
    return getattr(_load_hubconf(repo_dir, force_reload),
                   model)(*args, **kwargs)
