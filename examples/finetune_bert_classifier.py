"""Sequence classification with the BERT encoder family: synthetic
'sentiment' task where the label is determined by which marker token
appears — the classifier head + encoder finetune end-to-end.

Run: python examples/finetune_bert_classifier.py
"""

import _cpu_mesh  # noqa: F401

import numpy as np
import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu import optimizer as opt
from paddle_tpu.core.functional import extract_params, functional_call
from paddle_tpu.models import BertConfig, BertForSequenceClassification


def main():
    pt.seed(0)
    cfg = BertConfig(
        vocab_size=128, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, max_position_embeddings=32,
        num_labels=2, use_flash_attention=False,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0)
    model = BertForSequenceClassification(cfg)

    rng = np.random.default_rng(0)
    n, seq = 64, 16
    ids = rng.integers(5, 120, (n, seq))
    labels = rng.integers(0, 2, n)
    ids[np.arange(n), rng.integers(1, seq, n)] = np.where(labels, 3, 4)

    params = extract_params(model)
    optimizer = opt.AdamW(learning_rate=2e-3, multi_precision=False)
    state = optimizer.init(params)

    @jax.jit
    def step(params, state, x, y):
        def loss_fn(p):
            logits = functional_call(model, p, x)
            return pt.nn.functional.cross_entropy(logits, y)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        params, state = optimizer.update(grads, state, params)
        return params, state, loss

    x = jnp.asarray(ids)
    y = jnp.asarray(labels)
    for i in range(60):
        params, state, loss = step(params, state, x, y)
    pred = jnp.argmax(functional_call(model, params, x), -1)
    acc = float((pred == y).mean())
    print(f"final loss {float(loss):.4f}, accuracy {acc:.2%}")
    assert acc > 0.9


if __name__ == "__main__":
    main()
