"""Shared example bootstrap: run on an 8-device virtual CPU mesh so every
example works on any machine (swap for real TPU devices in production —
nothing else changes)."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:  # drop the sandbox's remote-TPU plugin if present
    from jax._src import xla_bridge as _xb

    for _reg in ("_backend_factories", "backend_factories"):
        _d = getattr(_xb, _reg, None)
        if isinstance(_d, dict):
            _d.pop("axon", None)
except Exception:
    pass
