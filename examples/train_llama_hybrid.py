"""Pretrain a (tiny) Llama with hybrid parallelism — ZeRO-3 x tensor
parallel x data parallel over an 8-device mesh — plus gradient
accumulation, checkpoint save, and resume.

Run: python examples/train_llama_hybrid.py
"""

import _cpu_mesh  # noqa: F401  (device bootstrap — must be first)

import numpy as np
import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu import distributed as dist, optimizer as opt
from paddle_tpu.distributed import checkpoint as ckpt
from paddle_tpu.distributed.strategy import DistributedStrategy, HybridConfig
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
from paddle_tpu.trainer import TrainStep


def main():
    pt.seed(0)
    cfg = LlamaConfig.tiny(num_hidden_layers=2, use_flash_attention=False)
    model = LlamaForCausalLM(cfg)

    strategy = DistributedStrategy()
    strategy.hybrid_configs = HybridConfig(
        dp_degree=2, sharding_degree=2, mp_degree=2)
    strategy.sharding = True
    strategy.sharding_configs.stage = 3          # ZeRO-3
    strategy.gradient_merge = True               # 2 micro-batches/step
    strategy.gradient_merge_k_steps = 2
    mesh = dist.build_mesh(dp=2, fsdp=2, tp=2)

    ts = TrainStep(
        model,
        opt.AdamW(learning_rate=3e-3, weight_decay=0.01,
                  grad_clip=opt.ClipGradByGlobalNorm(1.0),
                  multi_precision=False),
        mesh, strategy,
    )

    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (8, 32)))
    batch = {"input_ids": ids, "labels": ids}
    losses = [float(ts.run(batch)) for _ in range(10)]
    print(f"loss: {losses[0]:.3f} -> {losses[-1]:.3f}")
    assert losses[-1] < losses[0]

    # sharded checkpoint → fresh trainer on a DIFFERENT topology resumes
    import tempfile

    path = tempfile.mkdtemp(prefix="llama_ckpt_")
    ckpt.save_state_dict(ts.state_dict()["params"], path)
    mesh2 = dist.build_mesh(fsdp=4, tp=2)        # reshard on load
    strategy2 = DistributedStrategy()
    strategy2.hybrid_configs = HybridConfig(sharding_degree=4, mp_degree=2)
    strategy2.sharding = True
    strategy2.sharding_configs.stage = 3
    ts2 = TrainStep(model, opt.AdamW(3e-3, multi_precision=False),
                    mesh2, strategy2)
    restored = ckpt.load_state_dict(
        path, target=ts2.state_dict()["params"])
    ts2.set_state_dict({"params": restored})
    resumed = float(ts2.run(batch))
    print(f"resumed on a different mesh, loss: {resumed:.3f}")
    assert resumed < losses[0]


if __name__ == "__main__":
    main()
