"""A PaddlePaddle training script, ported by changing ONE import.

Every pattern below is written the way paddle tutorials write it —
fleet.init + DistributedStrategy, ParamAttr, DataParallel, Tensor
METHODS (x.numpy(), x.cast(...), x.unsqueeze(...)), paddle.io DataLoader,
amp.auto_cast + GradScaler, LR scheduler stepping, state_dict
save/load — and runs unchanged on the TPU stack (here: an 8-device
virtual CPU mesh; swap devices for real chips, nothing else changes).

Run: python examples/migrate_from_paddle.py
"""

import _cpu_mesh  # noqa: F401  (device bootstrap — must be first)

import numpy as np

import paddle_tpu as paddle  # the one-line port
from paddle_tpu import nn
from paddle_tpu.distributed import fleet


class MLP(nn.Layer):
    def __init__(self):
        super().__init__()
        # paddle idiom: ParamAttr controls init/trainability per-param
        self.fc1 = nn.Linear(
            16, 64,
            weight_attr=paddle.ParamAttr(
                initializer=nn.initializer.KaimingNormal()))
        self.act = nn.GELU()
        self.fc2 = nn.Linear(64, 4)

    def forward(self, x):
        return self.fc2(self.act(self.fc1(x)))


def main():
    paddle.seed(0)

    # fleet init, exactly as the collective tutorials do
    strategy = fleet.DistributedStrategy()
    fleet.init(is_collective=True, strategy=strategy)
    print(f"worker {fleet.worker_index()}/{fleet.worker_num()}")

    model = paddle.DataParallel(MLP())
    sched = paddle.optimizer.lr.CosineAnnealingDecay(
        learning_rate=1e-2, T_max=20)
    opt = paddle.optimizer.AdamW(learning_rate=sched,
                                 weight_decay=0.01)
    opt = fleet.distributed_optimizer(opt)

    # paddle.io data pipeline
    rng = np.random.default_rng(0)
    xs = rng.standard_normal((256, 16)).astype("float32")
    ys = (xs[:, :4].sum(axis=1) > 0).astype("int64") + 2 * (
        xs[:, 4:8].sum(axis=1) > 0).astype("int64")
    dataset = paddle.io.TensorDataset([xs, ys])
    loader = paddle.io.DataLoader(dataset, batch_size=32, shuffle=True)

    scaler = paddle.amp.GradScaler(enable=False)  # bf16 needs no scaling
    from paddle_tpu.trainer import build_train_step
    from paddle_tpu.distributed import build_mesh

    def loss_fn(logits, label):
        return nn.functional.cross_entropy(logits, label).mean()

    step = build_train_step(model, opt, build_mesh(dp=8),
                            loss_fn=loss_fn)

    losses = []
    for epoch in range(3):
        for batch in loader():
            x, y = batch
            # tensor METHODS, the way paddle scripts touch data (the
            # loader yields host arrays — the TPU-first pipeline keeps
            # augmentation off-device; to_tensor is the device hop)
            x = paddle.to_tensor(x).cast("float32")
            y = paddle.to_tensor(y)
            with paddle.amp.auto_cast(enable=False):
                loss = step.run({"input": x, "label": y})
            losses.append(float(loss))
    print(f"first loss {losses[0]:.4f} -> last {losses[-1]:.4f}")
    assert losses[-1] < losses[0], "training must reduce the loss"

    # eval using the method surface end-to-end
    step.sync_to_model()
    model.eval()
    logits = model(paddle.to_tensor(xs))
    pred = logits.argmax(axis=-1)
    acc = float(pred.equal(paddle.to_tensor(ys)).cast(
        "float32").mean())
    print(f"train-set accuracy ({len(xs)}): {acc:.2f}")
    assert acc > 0.5

    # checkpoint round-trip through the paddle save/load surface
    import tempfile, os  # noqa: E401

    d = tempfile.mkdtemp()
    path = os.path.join(d, "mlp.pdparams")
    paddle.save(model.state_dict(), path)
    model2 = paddle.DataParallel(MLP())
    model2.set_state_dict(paddle.load(path))
    l2 = model2(paddle.to_tensor(xs[:8]))
    np.testing.assert_allclose(np.asarray(l2), np.asarray(logits[:8]),
                               rtol=1e-5, atol=1e-6)
    print("checkpoint round-trip exact")


if __name__ == "__main__":
    main()
