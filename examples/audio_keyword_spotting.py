"""Keyword-spotting-style audio pipeline: waveform → MFCC features →
small conv classifier, trained with RMSProp via the DataLoader.

Run: python examples/audio_keyword_spotting.py
"""

import _cpu_mesh  # noqa: F401

import numpy as np
import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu import audio, io, nn, optimizer as opt
from paddle_tpu.core.functional import extract_params, functional_call


def make_dataset(n_per_class=16, sr=16000):
    """Four synthetic 'keywords': tones at distinct frequencies with
    noise + random phase."""
    rng = np.random.default_rng(0)
    t = np.arange(sr // 4) / sr
    waves, labels = [], []
    for label, f0 in enumerate([300.0, 700.0, 1500.0, 3000.0]):
        for _ in range(n_per_class):
            phase = rng.random() * 2 * np.pi
            w = np.sin(2 * np.pi * f0 * t + phase)
            w += 0.1 * rng.normal(size=t.shape)
            waves.append(w.astype(np.float32))
            labels.append(label)
    return np.stack(waves), np.array(labels)


def main():
    pt.seed(0)
    waves, labels = make_dataset()
    ds = io.TensorDataset(waves, labels)
    loader = io.DataLoader(ds, batch_size=16, shuffle=True)

    mfcc = audio.MFCC(sr=16000, n_mfcc=13, n_fft=256, n_mels=40)

    class KWS(nn.Layer):
        def __init__(self):
            super().__init__()
            self.proj = nn.Linear(13, 32)
            self.out = nn.Linear(32, 4)

        def forward(self, wave):
            feats = mfcc(wave)                 # [B, 13, frames]
            h = jnp.mean(feats, axis=-1)       # average over time
            return self.out(nn.functional.relu(self.proj(h)))

    model = KWS()
    optimizer = opt.RMSProp(learning_rate=2e-3)
    params = extract_params(model)
    state = optimizer.init(params)

    @jax.jit
    def step(params, state, x, y):
        def loss_fn(p):
            return nn.functional.cross_entropy(
                functional_call(model, p, x), y)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        params, state = optimizer.update(grads, state, params)
        return params, state, loss

    for epoch in range(20):
        for x, y in loader:
            params, state, loss = step(params, state, jnp.asarray(x),
                                       jnp.asarray(y))
    logits = functional_call(model, params, jnp.asarray(waves))
    acc = float((jnp.argmax(logits, -1) == jnp.asarray(labels)).mean())
    print(f"final loss {float(loss):.4f}, train accuracy {acc:.2%}")
    assert acc > 0.95


if __name__ == "__main__":
    main()
