"""Text generation with the AOT predictor: greedy, nucleus sampling, and
beam search over the same compiled prefill/decode programs.

Run: python examples/generate_text.py
"""

import _cpu_mesh  # noqa: F401

import numpy as np
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu.inference import Config, Predictor
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM


def main():
    pt.seed(0)
    cfg = LlamaConfig.tiny(num_hidden_layers=2,
                           use_flash_attention=False)
    model = LlamaForCausalLM(cfg)

    c = Config()
    c.max_seq_len = 64
    c.seq_buckets = (16, 32)
    c.decode_dtype = jnp.float32
    pred = Predictor(model, c)

    prompt = np.random.default_rng(0).integers(1, cfg.vocab_size, (2, 7))
    greedy = pred.generate(prompt, max_new_tokens=8)
    print("greedy   :", greedy[0], f"(TTFT {pred.last_ttft_ms:.0f} ms)")
    sampled = pred.generate(prompt, max_new_tokens=8,
                            decode_strategy="sampling", top_p=0.9,
                            temperature=0.8, seed=42)
    print("sampling :", sampled[0])
    beam = pred.generate(prompt, max_new_tokens=8,
                         decode_strategy="beam_search", num_beams=4,
                         length_penalty=0.6)
    print("beam(4)  :", beam[0])
    assert greedy.shape == sampled.shape == beam.shape == (2, 8)


if __name__ == "__main__":
    main()
