"""Benchmark: Llama pretraining step on the available TPU chip(s).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Metric: tokens/sec/chip for a causal-LM train step (fwd+bwd+AdamW update,
bf16 compute / fp32 master, ZeRO-3-equivalent sharding when >1 chip).
vs_baseline: BASELINE.json has "published": {} (no reference numbers), so
this reports the ratio against our own recorded first measurement when
BENCH_BASELINE.json exists, else 1.0.

Resilience contract (round-1 failed rc=1 on TPU-backend init): the TPU
backend is probed in a KILLABLE SUBPROCESS with retries/backoff — a hung
or failing PJRT init can never take this process down. If the TPU is
unreachable the benchmark still emits a valid JSON line from a CPU smoke
run, with the TPU failure diagnostics in "extra.tpu_probe".

Usage:
  python bench.py            # headline: llama train step
  python bench.py --config moe|vit|mamba|infer   # secondary benchmarks
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

# Per-attempt timeouts (first covers cold PJRT init) and the total
# window over which the tunnel is retried before the CPU fallback.
# Round-4 lesson: one-shot probes lost two consecutive driver captures
# to transient tunnel outages — the retry discipline must live in the
# tool, not in session lore.
PROBE_ATTEMPT_TIMEOUTS = (240, 120)
PROBE_WINDOW_S = float(os.environ.get("BENCH_PROBE_WINDOW_S", "600"))
# marker argv appended to probe children so an orphaned hung probe is
# recognizable to the reaper (python -c ignores extra argv)
PROBE_MARK = "--paddle-tpu-bench-probe"


def _stale_chip_holders():
    """Orphaned python processes from a previous crashed bench/entry run.
    libtpu is single-process-exclusive: a leftover child that still holds
    the TPU client makes every later probe fail until it dies."""
    holders = []
    try:
        pids = [p for p in os.listdir("/proc") if p.isdigit()]
    except OSError:
        return holders
    for pid in pids:
        if int(pid) == os.getpid():
            continue
        try:
            with open(f"/proc/{pid}/cmdline", "rb") as f:
                argv = f.read().split(b"\0")
            with open(f"/proc/{pid}/stat") as f:
                ppid = int(f.read().rsplit(")", 1)[1].split()[1])
        except (OSError, IndexError, ValueError):
            continue
        # argv[0] must BE a python interpreter — a shell/driver whose
        # command *string* merely mentions bench.py must never match
        exe = os.path.basename(argv[0].decode("utf-8", "replace"))
        if not exe.startswith("python"):
            continue
        cmd = " ".join(a.decode("utf-8", "replace") for a in argv if a)
        # conservative: only reap processes that were orphaned (their
        # launching bench/driver is gone) AND are recognizably ours
        if ppid == 1 and ("bench.py" in cmd or "__graft_entry__" in cmd
                          or PROBE_MARK in cmd):
            holders.append((int(pid), cmd.strip()[:120]))
    return holders


_HB_PREFIX = "/tmp/paddle_tpu_bench.hb."


def _heartbeat():
    """Refresh this process's liveness file. Any bench that might be
    orphaned (nohup) stays immune to the reaper while it keeps beating —
    the probe loop beats every attempt, so ≤ ~4 min between beats; a
    crashed run's orphans never beat again."""
    try:
        with open(f"{_HB_PREFIX}{os.getpid()}", "w") as f:
            f.write(str(time.time()))
    except OSError:
        pass


def _heartbeat_fresh(pid, max_age_s=400.0):
    try:
        return (time.time()
                - os.stat(f"{_HB_PREFIX}{pid}").st_mtime) < max_age_s
    except OSError:
        return False


def _clear_heartbeat():
    try:
        os.unlink(f"{_HB_PREFIX}{os.getpid()}")
    except OSError:
        pass


def _proc_cpu_jiffies(pid):
    try:
        with open(f"/proc/{pid}/stat") as f:
            parts = f.read().rsplit(")", 1)[1].split()
        return int(parts[11]) + int(parts[12])  # utime + stime
    except (OSError, IndexError, ValueError):
        return None


def _gc_heartbeats(max_age_s=3600.0):
    """/tmp hygiene only: drop heartbeat files nobody will clear (killed
    parents). The reaper's shield window is _heartbeat_fresh's 400s
    check — by the time this GC fires, the file shields nothing."""
    import glob

    for f in glob.glob(_HB_PREFIX + "*"):
        try:
            if time.time() - os.stat(f).st_mtime > max_age_s:
                os.unlink(f)
        except OSError:
            pass


def _reap_stale_holders(diags):
    """Kill matched orphans — but only ones that are IDLE (no CPU over a
    sample window). A wedged holder is blocked on a dead tunnel socket
    and burns no CPU; a healthy daemonized benchmark that happens to be
    orphaned (nohup) keeps accumulating jiffies and is left alone."""
    import signal

    _gc_heartbeats()
    candidates = _stale_chip_holders()
    if not candidates:
        return
    # a candidate with a live CHILD is a supervisor (e.g. a nohup'd
    # bench.py blocked in subprocess.run — 0 CPU but healthy); the chip
    # holder in that tree is the child, whose parent is alive, so it
    # never matches the orphan rule. Only childless orphans are reapable.
    with_children = set()
    try:
        for pid in os.listdir("/proc"):
            if pid.isdigit():
                try:
                    with open(f"/proc/{pid}/stat") as f:
                        with_children.add(
                            int(f.read().rsplit(")", 1)[1].split()[1]))
                except (OSError, IndexError, ValueError):
                    pass
    except OSError:
        pass
    before = {pid: _proc_cpu_jiffies(pid) for pid, _ in candidates}
    time.sleep(1.5)
    for pid, cmd in candidates:
        if pid in with_children:
            diags.append({"spared_supervisor_pid": pid, "cmd": cmd})
            continue
        if _heartbeat_fresh(pid):
            # healthy orphan (e.g. nohup'd run sleeping between its own
            # probe attempts): its heartbeat file is still beating
            diags.append({"spared_heartbeat_pid": pid, "cmd": cmd})
            continue
        b, a = before.get(pid), _proc_cpu_jiffies(pid)
        if b is None or a is None:  # already gone
            continue
        if a > b:
            diags.append({"spared_live_pid": pid, "cmd": cmd})
            continue
        try:
            os.kill(pid, signal.SIGKILL)
            diags.append({"reaped_stale_pid": pid, "cmd": cmd})
        except OSError:
            pass


def probe_tpu():
    """Bring up the TPU backend in a killable child, retrying over a
    bounded window (stale-holder reaping between attempts). Returns
    (ok, diagnostics)."""
    code = (
        "import jax; ds = jax.devices(); "
        "import jax.numpy as jnp; "
        "x = jnp.ones((128, 128)); "
        "print((x @ x).sum()); "
        "print('PROBE_OK', len(ds), ds[0].platform)"
    )
    diags = []
    deadline = time.time() + PROBE_WINDOW_S
    _heartbeat()
    # reap BEFORE the first attempt too: if a crashed run left a wedged
    # holder, attempt 0 would otherwise burn its full cold-init timeout
    _reap_stale_holders(diags)
    attempt = 0
    while True:
        _heartbeat()
        tmo = PROBE_ATTEMPT_TIMEOUTS[
            min(attempt, len(PROBE_ATTEMPT_TIMEOUTS) - 1)]
        tmo = min(tmo, max(30, deadline - time.time()))
        t0 = time.time()
        try:
            r = subprocess.run(
                [sys.executable, "-c", code, PROBE_MARK],
                capture_output=True, text=True, timeout=tmo,
            )
            if r.returncode == 0 and "PROBE_OK" in r.stdout:
                return True, diags
            diags.append({
                "attempt": attempt, "rc": r.returncode,
                "elapsed_s": round(time.time() - t0, 1),
                "stderr_tail": r.stderr[-800:],
            })
        except subprocess.TimeoutExpired:
            diags.append({
                "attempt": attempt, "rc": "timeout",
                "elapsed_s": round(time.time() - t0, 1),
                "stderr_tail": f"probe hung > {tmo}s (PJRT init stall)",
            })
        attempt += 1
        if time.time() + 35 >= deadline:
            break
        _reap_stale_holders(diags)
        time.sleep(min(15.0, 5.0 * attempt))
    # keep the diagnostics bounded for the JSON line / details file
    if len(diags) > 8:
        diags = diags[:2] + [{"elided_attempts": len(diags) - 4}] + diags[-2:]
    return False, diags


def _llama_cfg(platform):
    import os

    from paddle_tpu.models import LlamaConfig

    if platform == "tpu":
        # ~880M-param Llama, remat OFF. Tuned on the v5e chip (round 3
        # sweep): wider beats deeper — the MXU runs the h×(8/3·h) MLP
        # GEMMs at higher utilization than many small ones, and remat
        # on a model that fits costs ~1/3 extra FLOPs the 6·N·tok MFU
        # formula doesn't credit (round 2's 36% was mostly that tax).
        # Measured: h1536/L16 47.7%, h2048/L12 50.8%, h2560/L8 52.0%,
        # h3072/L6 56.3% MFU. Params bf16 + fp32 master + AdamW moments
        # ≈ 14 B/param ≈ 12.3 GB; batch 4×2048 no-remat activations fit
        # the 16 GB HBM.
        hid = int(os.environ.get("BENCH_HID", "3072"))
        inter = int(os.environ.get("BENCH_INTER", str(int(hid * 8 // 3 // 128 * 128))))
        layers = int(os.environ.get("BENCH_LAYERS", "6"))
        batch = int(os.environ.get("BENCH_BATCH", "4"))
        remat = os.environ.get("BENCH_REMAT", "0") == "1"
        return LlamaConfig(
            vocab_size=32000,
            hidden_size=hid,
            intermediate_size=inter,
            num_hidden_layers=layers,
            num_attention_heads=hid // 128,  # head_dim 128 → flash kernel
            num_key_value_heads=hid // 128,
            max_position_embeddings=2048,
            use_flash_attention=True,
            use_recompute=remat,
            dtype="bfloat16",
        ), batch, 2048, 10
    # CPU smoke: tiny but same code path
    return LlamaConfig(
        vocab_size=512,
        hidden_size=256,
        intermediate_size=512,
        num_hidden_layers=2,
        num_attention_heads=2,
        num_key_value_heads=2,
        max_position_embeddings=256,
        use_flash_attention=False,
        dtype="float32",
    ), 2, 256, 3


def bench_llama_train(tpu_diags):
    import jax
    import jax.numpy as jnp
    import numpy as np

    import paddle_tpu as pt
    from benchmarks.devtime import (
        check_plausible,
        compiled_flops,
        fetch_sync,
        peak_flops,
        traced_step_ms,
    )
    from paddle_tpu import distributed as dist, optimizer as opt
    from paddle_tpu.distributed.strategy import (
        DistributedStrategy,
        HybridConfig,
    )
    from paddle_tpu.models import LlamaForCausalLM
    from paddle_tpu.trainer import TrainStep

    devices = jax.devices()
    n = len(devices)
    platform = devices[0].platform

    cfg, batch, seq, iters = _llama_cfg(platform)
    if batch % n:
        # batch must divide the dp×fsdp sharding (multi-device CPU smoke)
        batch = n * max(1, batch // n)

    pt.seed(0)
    model = LlamaForCausalLM(cfg)
    if cfg.dtype == "bfloat16":
        model.to(pt.bfloat16)

    # BENCH_MOMENT_DTYPE=bfloat16: halve Adam moment storage — the
    # update step is HBM-roofline (10% of the b4 headline), so this is
    # a direct ~3% step-time lever; measure against the fp32 default
    moment_dtype = _norm_moment_dtype(os.environ.get("BENCH_MOMENT_DTYPE"))
    optimizer = opt.AdamW(
        learning_rate=3e-4, weight_decay=0.01,
        multi_precision=(cfg.dtype == "bfloat16"),
        grad_clip=opt.ClipGradByGlobalNorm(1.0),
        moment_dtype=moment_dtype,
    )
    strategy = DistributedStrategy()
    if n > 1:
        strategy.hybrid_configs = HybridConfig(sharding_degree=n)
        strategy.sharding = True
        strategy.sharding_configs.stage = 3
        mesh = dist.build_mesh(fsdp=n, devices=devices)
    else:
        mesh = dist.build_mesh(devices=devices)

    # master_only drops the persistent bf16 param copies (the fp32
    # master is the single resident form; compute views are cast in-step)
    # — saves 2 B/param ≈ 1.75 GB on the 876M headline, bit-identical
    # numerics. That headroom is what admits batch 6.
    residency = os.environ.get(
        "BENCH_RESIDENCY",
        "master_only" if cfg.dtype == "bfloat16" else "paired")
    ts = TrainStep(model, optimizer, mesh, strategy,
                   master_residency=residency)
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (batch, seq)))
    data = {"input_ids": ids, "labels": ids}

    # warmup / compile, with a REAL completion fetch (block_until_ready
    # can return early through the tunnel — round-4 postmortem)
    fetch_sync(ts.run(data))
    fetch_sync(ts.run(data))

    # device-time-true step time: N steps inside a profiler trace; the
    # reported throughput comes from the trace's device plane, never
    # from wall clock through the tunnel
    n_steps = min(iters, 5) if platform == "tpu" else 2
    timing = traced_step_ms(lambda: ts.run(data), n_steps=n_steps)
    loss = ts.run(data)

    step_s = timing.step_ms / 1e3
    tokens_per_sec_chip = batch * seq / step_s / n

    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    peak = peak_flops(devices[0])
    # MFU denominator: XLA's own cost analysis of the compiled step
    # (includes attention + remat); fall back to the 6*N*T estimate
    # (per-chip: global-batch tokens divided over n chips, matching
    # the per-device step time the guard compares against)
    flops = compiled_flops(ts.lower(data))
    flops_src = "xla_cost_analysis"
    if flops is None:
        flops = 6.0 * n_params * batch * seq / n
        flops_src = "6NT_estimate"
    plaus = check_plausible(flops, timing.step_ms, devices[0])
    mfu = plaus.get("mfu_est")
    if platform == "tpu" and timing.device_step_ms is None:
        # wall clock through the tunnel is not a throughput basis
        plaus = {"implausible": True, "mfu_est": None,
                 "reason": "profiler trace carried no device plane; "
                           "tunnel wall-clock refused as a throughput "
                           "basis"}
        mfu = None

    vs = 1.0

    extra = {
        "n_chips": n,
        "platform": platform,
        "device_kind": getattr(devices[0], "device_kind", "?"),
        "peak_flops": peak,
        "params": n_params,
        "batch": batch,
        "seq": seq,
        "remat": cfg.use_recompute,
        "residency": residency,
        "moment_dtype": str(moment_dtype or "float32"),
        "step_ms": round(timing.step_ms, 2),
        "device_step_ms": (round(timing.device_step_ms, 2)
                           if timing.device_step_ms else None),
        "wall_step_ms": round(timing.wall_step_ms, 2),
        "timed_steps": timing.n_steps,
        "flops_per_step": flops,
        "flops_source": flops_src,
        "mfu_est": mfu,
        "loss": float(loss),
    }
    if timing.op_summary is not None and timing.op_summary.rows:
        ops = timing.op_summary
        total = ops.total_ms
        extra["op_summary"] = {
            "total_device_ms": round(total, 2),
            "timed_steps": timing.n_steps,
            "categories": {
                k: round(100.0 * v / total, 1)
                for k, v in ops.by_category().items()
            },
            "top_ops": [
                {"name": r.name[:60], "ms": round(r.total_ms, 2),
                 "count": r.count}
                for r in ops.rows[:8]
            ],
        }
    if tpu_diags:
        extra["tpu_probe"] = tpu_diags
    if plaus.get("implausible"):
        # computed FLOP/s above chip peak: refuse to report (round-4
        # lesson — 4 of 5 secondary numbers were dispatch-time artifacts)
        extra["refused_value"] = round(tokens_per_sec_chip, 1)
        extra["error"] = plaus.get("reason")
        return {
            "metric": "llama_train_implausible",
            "value": 0.0,
            "unit": "error",
            "vs_baseline": 0.0,
            "extra": extra,
        }
    name = (f"llama{n_params // 10**6}m_train_tokens_per_sec_per_chip"
            if platform == "tpu"
            else "llama_train_cpu_smoke_tokens_per_sec")
    return {
        "metric": name,
        "value": round(tokens_per_sec_chip, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(vs, 3),
        "extra": extra,
    }


BASELINE_PATH = os.path.join(os.path.dirname(__file__),
                             "BENCH_BASELINE.json")
DETAILS_PATH = os.path.join(os.path.dirname(__file__),
                            "BENCH_DETAILS.json")
CAPTURE_PATH = os.path.join(os.path.dirname(__file__),
                            "BENCH_TPU_CAPTURE.json")
MAX_LINE_BYTES = 2000


def _device_capture_pointer():
    """Identity of the freshest COMMITTED device-plane capture
    (timestamp + commit + headline metric), or None. When the tunnel
    probe fails and the ledger line records a CPU fallback, this
    pointer rides along so the driver artifact still names verifiable
    device evidence instead of a bare smoke number (VERDICT r5
    next-#2: three consecutive rounds of ``platform: cpu`` ledgers
    with the real capture only discoverable by reading the repo)."""
    try:
        with open(CAPTURE_PATH) as f:
            cap = json.load(f)
        head = (cap.get("configs", {}) or {}).get(
            cap.get("headline"), {}) or {}
        out = {"captured_at": cap.get("captured_at"),
               "metric": head.get("metric"), "value": head.get("value"),
               "unit": head.get("unit")}
        if not any(out.values()):
            return None
    except Exception:
        return None
    try:
        r = subprocess.run(
            ["git", "log", "-1", "--format=%h %cI", "--",
             os.path.basename(CAPTURE_PATH)],
            cwd=os.path.dirname(os.path.abspath(CAPTURE_PATH)),
            capture_output=True, text=True, timeout=10)
        if r.returncode == 0 and r.stdout.strip():
            sha, _, ciso = r.stdout.strip().partition(" ")
            out["commit"] = sha
            out["committed_at"] = ciso
    except Exception:
        pass  # pointer without provenance beats no pointer
    return out


def _compact_line(result):
    """Build the driver-facing JSON line: always parseable, < 2KB.

    Round 3 lost its headline because the printed line carried full
    tracebacks + per-secondary probe diagnostics and defeated the
    driver's tail parse. Full diagnostics now go to BENCH_DETAILS.json;
    the printed line keeps scalars only, with errors truncated hard.
    """
    details_error = None
    try:
        with open(DETAILS_PATH, "w") as f:
            json.dump(result, f, indent=1, default=str)
    except Exception as e:
        details_error = repr(e)[:120]

    def _err_msg(e):
        e = e or {}
        msg = (e.get("error") or e.get("stderr") or e.get("reason")
               or e.get("traceback")
               or (f"timeout after {e['timeout_s']}s"
                   if "timeout_s" in e else ""))
        return str(msg).strip()[-120:]

    out = {k: result.get(k)
           for k in ("metric", "value", "unit", "vs_baseline")}
    extra = result.get("extra", {}) or {}
    keep = {k: extra[k] for k in
            ("platform", "n_chips", "device_kind", "params", "batch",
             "seq", "remat", "residency", "moment_dtype", "step_ms",
             "device_step_ms", "mfu_est", "loss") if k in extra}
    if result.get("unit") == "error":
        keep["error"] = _err_msg(extra)
    if details_error:
        keep["details_error"] = details_error
    if "tpu_probe" in extra:
        keep["tpu_probe"] = "tpu unavailable; see BENCH_DETAILS.json"
    if extra.get("platform") == "cpu":
        # ANY cpu-plane headline (probe failure OR an explicit
        # JAX_PLATFORMS=cpu run) names its device evidence — the
        # ledger must never show a bare smoke number when a committed
        # capture exists
        ptr = _device_capture_pointer()
        if ptr:
            keep["last_device_capture"] = ptr
    sec = extra.get("secondary")
    if sec:
        keep["secondary"] = {}
        for name, r in sec.items():
            row = {"metric": r.get("metric"), "value": r.get("value"),
                   "unit": r.get("unit")}
            if "vs_baseline" in r:
                row["vs_baseline"] = r["vs_baseline"]
            if r.get("unit") in ("error", "skipped"):
                row["error"] = _err_msg(r.get("extra"))
            # goodput-under-SLO headline (serve7b): the mid-QPS row's
            # scalars ride the ledger line — the engine's metrics_
            # snapshot() is one document now, no stitching here
            gp = (r.get("extra") or {}).get("goodput_under_slo") or {}
            sweep = gp.get("sweep") or []
            if sweep:
                # (n-1)//2: the true middle row — n//2 would pick the
                # LAST (worst-goodput) row of an even-length sweep
                mid = sweep[(len(sweep) - 1) // 2]
                row["goodput"] = {
                    k: mid.get(k) for k in
                    ("qps", "goodput", "p99_ttft_ms", "p99_tpot_ms",
                     "burn_rate")}
            # flight-data scalars (serve7b): peak SLO burn across the
            # sweep, p50 attributed request device-ms, alert firings —
            # the trend-shaped numbers the ledger trajectory
            # accumulates (shed-path included below)
            fl = gp.get("flight") or {}
            if fl:
                row["flight"] = {
                    k: fl.get(k) for k in
                    ("burn_rate_peak", "req_device_ms_p50",
                     "alerts_fired")}
            # scheduler A/B scalars (serve7b): FIFO-vs-SLO-fair
            # goodput at the saturated burst plus the starvation
            # adversary's worst-small-tenant TTFT bound — the numbers
            # that rank admission policies on the ledger
            sa = (r.get("extra") or {}).get("sched_ab") or {}
            if sa:
                row["sched_ab"] = {
                    "fifo_goodput": (sa.get("fifo") or {}).get(
                        "goodput"),
                    "slo_fair_goodput": (sa.get("slo_fair") or {})
                    .get("goodput"),
                    "preemptions": (sa.get("slo_fair") or {}).get(
                        "preemptions"),
                    "starve_bound_x": (sa.get("starvation") or {})
                    .get("bound_factor"),
                }
            # HTTP front-door overhead (serve7b): server-path tok/s
            # beside the library path — the wire tax, measured over a
            # real loopback socket
            hf = (r.get("extra") or {}).get("http_front_door") or {}
            if hf:
                row["http_front_door"] = {
                    k: hf.get(k) for k in
                    ("library_tokens_per_sec", "http_tokens_per_sec",
                     "overhead_pct")}
            # quantized-serving scalars (serve7b): the MODELED compound
            # ×-factor names the expected win on the ledger before the
            # TPU window, and outputs_match/first_divergence carry the
            # measured quality delta with it
            qs = (r.get("extra") or {}).get("quant") or {}
            if qs:
                row["quant"] = {
                    k: qs.get(k) for k in
                    ("modeled_int8_w_x", "modeled_compound_x",
                     "outputs_match", "first_divergence")}
            # replicated-serving scalars (serve7b): the failover count
            # plus the outputs_match bit carry the fleet's determinism
            # claim on the ledger with the storm's wall overhead
            rf = (r.get("extra") or {}).get("replica_failover") or {}
            if rf:
                row["replica_failover"] = {
                    k: rf.get(k) for k in
                    ("failovers", "outputs_match",
                     "failover_overhead_pct")}
            # contract-audit verdict (serve7b): the repo program
            # set's ptaudit result rides the ledger — programs
            # audited, op-counts-ok bit, violation count — so a
            # donation/dtype/size regression is visible on the same
            # line as the perf numbers it would silently rot
            au = (r.get("extra") or {}).get("audit") or {}
            if au:
                row["audit"] = {
                    k: au.get(k) for k in
                    ("programs", "op_counts_ok", "violations")}
            # measured-vs-modeled step breakdown (serve7b): the
            # decode-chunk measured p50 beside its HBM floor, plus
            # the recompile-watchdog verdict, ride the ledger so the
            # driver sees MEASUREMENTS next to the models
            sb = (r.get("extra") or {}).get("step_breakdown") or {}
            sb_rows = {x.get("program"): x for x in sb.get("rows", [])}
            dc = sb_rows.get("decode_chunk")
            if dc:
                row["step_breakdown"] = {
                    "decode_chunk_ms": dc.get("measured_p50_ms"),
                    "decode_floor_ms": dc.get("modeled_floor_ms"),
                    "prefill_chunk_ms": (sb_rows.get("prefill_chunk")
                                         or {}).get("measured_p50_ms"),
                    "recompiles": sum(
                        (sb.get("recompiles_post_seal") or {})
                        .values()),
                }
            keep["secondary"][name] = row
    out["extra"] = keep

    line = json.dumps(out)
    # belt-and-braces: progressively shed detail until the line fits
    if len(line) > MAX_LINE_BYTES and "secondary" in keep:
        for row in keep["secondary"].values():
            row.pop("error", None)
            row.pop("goodput", None)
            row.pop("flight", None)
            row.pop("sched_ab", None)
            row.pop("http_front_door", None)
            row.pop("quant", None)
            row.pop("replica_failover", None)
            row.pop("audit", None)
            row.pop("step_breakdown", None)
        line = json.dumps(out)
    if len(line) > MAX_LINE_BYTES:
        # the capture pointer survives the final shed: a truncated CPU
        # fallback line must still name its device evidence
        out["extra"] = {k: keep[k] for k in
                        ("platform", "n_chips", "last_device_capture")
                        if k in keep}
        line = json.dumps(out)
    return line


def _load_baseline():
    try:
        with open(BASELINE_PATH) as f:
            return json.load(f)
    except Exception:
        return None


def _maybe_write_baseline(result):
    """First green TPU measurement (headline + any green secondaries)
    becomes the recorded baseline, so vs_baseline is a real
    round-over-round regression signal — per config, not just the
    headline."""
    if result.get("unit") == "error":
        return
    if result.get("extra", {}).get("platform") != "tpu":
        return
    base = _load_baseline() or {}
    changed = False
    if "value" not in base:
        base.update({"metric": result["metric"],
                     "value": result["value"],
                     "unit": result["unit"],
                     "extra": {k: v for k, v in
                               result.get("extra", {}).items()
                               if k != "secondary"}})
        changed = True
    secondary = result.get("extra", {}).get("secondary", {})
    base_sec = base.setdefault("secondary", {})
    for name, r in secondary.items():
        # keyed by METRIC, not config name: a config with variants
        # (serve7b int8 vs int4) must never cross-compare dtypes
        key = r.get("metric", name)
        if (key not in base_sec and r.get("unit") not in
                ("error", "skipped") and
                r.get("extra", {}).get("platform") == "tpu"):
            base_sec[key] = {"metric": r["metric"], "value": r["value"],
                             "unit": r["unit"]}
            # config variants the ratio must never silently fold in
            for variant in ("compute_dtype", "conv_layout"):
                if variant in r.get("extra", {}):
                    base_sec[key][variant] = r["extra"][variant]
            changed = True
    if changed:
        with open(BASELINE_PATH, "w") as f:
            json.dump(base, f, indent=1)


def _apply_baseline_ratio(result):
    """Fill vs_baseline for the headline and each secondary from the
    recorded first-green-run values (TPU only)."""
    base = _load_baseline()
    if base is None:
        return
    if result.get("extra", {}).get("platform") == "tpu":
        try:
            result["vs_baseline"] = round(
                result["value"] / float(base["value"]), 3)
            # never cross-compare optimizer-state variants SILENTLY:
            # the ratio stays (it is a real speedup/regression of the
            # same training task) but the variant change is named
            b_md = base.get("extra", {}).get("moment_dtype", "float32")
            r_md = result.get("extra", {}).get("moment_dtype", "float32")
            if b_md != r_md:
                result["extra"]["vs_baseline_note"] = (
                    f"baseline ran moment_dtype={b_md}, this run {r_md}")
        except Exception:
            pass
    for name, r in result.get("extra", {}).get("secondary", {}).items():
        sec = base.get("secondary", {})
        b = sec.get(r.get("metric")) or sec.get(name)
        if (b and b.get("metric") == r.get("metric")
                and r.get("extra", {}).get("platform") == "tpu"
                and r.get("value")):
            r["vs_baseline"] = round(r["value"] / float(b["value"]), 3)
            # same rule as the headline's moment_dtype: the ratio stays
            # (same training task) but a config-variant change is NAMED
            # instead of silently folded into the 'speedup'. Baselines
            # recorded before this field existed were fp32/NCHW captures
            # (BASELINE.md round-5 note), hence the defaults.
            notes = []
            for variant, default in (("compute_dtype", "float32"),
                                     ("conv_layout", "NCHW")):
                b_v = b.get(variant, default)
                r_v = r.get("extra", {}).get(variant)
                if r_v is not None and r_v != b_v:
                    notes.append(f"baseline ran {variant}={b_v}, "
                                 f"this run {r_v}")
            if notes:
                r.setdefault("extra", {})["vs_baseline_note"] = \
                    "; ".join(notes)


SECONDARY_TIMEOUT = 560   # per config; each compiles its own programs
SERVE7B_TIMEOUT = 700     # 32-layer decode program compiles are slower
SECONDARY_BUDGET = 2400   # total wall-clock for all secondaries
HEADLINE_TIMEOUT = 1200


def _run_one_config(name, env, timeout):
    """Run ``bench.py --config name`` in a subprocess. The parent process
    NEVER initializes jax: libtpu is single-process-exclusive, so the
    device must be free for every child (headline included)."""
    try:
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--config", name],
            env=env, capture_output=True, text=True, timeout=timeout,
        )
        lines = [l for l in r.stdout.strip().splitlines()
                 if l.startswith("{")]
        if lines:
            return json.loads(lines[-1])
        return {"metric": f"bench_{name}_failed", "value": 0.0,
                "unit": "error", "vs_baseline": 0.0,
                "extra": {"rc": r.returncode, "stderr": r.stderr[-800:]}}
    except subprocess.TimeoutExpired:
        return {"metric": f"bench_{name}_timeout", "value": 0.0,
                "unit": "error", "vs_baseline": 0.0,
                "extra": {"timeout_s": timeout}}
    except Exception as e:
        return {"metric": f"bench_{name}_failed", "value": 0.0,
                "unit": "error", "vs_baseline": 0.0,
                "extra": {"error": repr(e)}}


def _run_secondary_configs(env):
    """Capture the remaining BASELINE.json configs (infer/moe/vit/mamba
    + unet) — one subprocess each (clean device state; one crash cannot
    take down the headline) under a global budget so the driver always
    gets its JSON line."""
    out = {}
    t_start = time.time()
    for name in ("infer", "moe", "vit", "mamba", "unet", "serve7b"):
        if time.time() - t_start > SECONDARY_BUDGET:
            out[name] = {"metric": f"bench_{name}_skipped", "value": 0.0,
                         "unit": "skipped",
                         "extra": {"reason": "secondary budget exhausted"}}
            continue
        tmo = SERVE7B_TIMEOUT if name == "serve7b" else SECONDARY_TIMEOUT
        _heartbeat()
        out[name] = _run_one_config(name, env, tmo)
    return out


def _norm_moment_dtype(s):
    """Validate/normalize BENCH_MOMENT_DTYPE up front — a typo must die
    in milliseconds, not after the probe window + an 876M model build."""
    s = (s or "").strip().lower()
    if s in ("", "float32", "fp32", "f32"):
        return None
    if s in ("bfloat16", "bf16"):
        return "bfloat16"
    raise ValueError(
        f"BENCH_MOMENT_DTYPE={s!r}: use 'float32' or 'bfloat16'")


def _enable_compile_cache():
    """Persistent XLA compilation cache, shared by every bench child on
    this machine (/tmp). Tunnel time is the scarce resource: the 7B
    serving config alone compiles for minutes, and the driver's
    end-of-round capture re-runs the exact programs this session already
    compiled. Fully best-effort — a backend that can't serialize
    executables just misses."""
    if os.environ.get("BENCH_NO_COMPILE_CACHE"):
        return
    try:
        import jax

        path = os.environ.get("BENCH_COMPILE_CACHE_DIR",
                              "/tmp/paddle_tpu_xla_cache")
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        # cache even fast compiles: through the tunnel, *dispatching* a
        # compile is the expensive part, not the compile itself
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except Exception:
        pass


def _child_main(config):
    """Child mode (--config X): the parent guarantees the device is free
    for this process; run the requested benchmark in-process. Children
    do NOT heartbeat: while the parent lives they are not orphan-
    matchable, and after a parent crash a wedged child must be
    immediately reapable."""
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        # direct `--config X` invocations don't pass through the
        # parent's env scrub, and sitecustomize registers the axon
        # plugin at interpreter BOOT — before any code here can unset
        # env. The config route works post-registration (same as
        # tests/conftest.py): pin the platform before first backend use
        # or jax.devices() blocks for minutes on the wedged tunnel.
        import jax

        jax.config.update("jax_platforms", "cpu")
    _enable_compile_cache()
    tpu_diags = None
    if os.environ.get("_BENCH_DIAGS"):
        tpu_diags = json.loads(os.environ["_BENCH_DIAGS"])
    try:
        if config == "llama":
            result = bench_llama_train(tpu_diags)
        else:
            from benchmarks.suite import run_config

            result = run_config(config, tpu_diags)
    except Exception as e:  # last-resort: never exit silently nonzero
        import traceback

        result = {
            "metric": f"bench_{config}_failed",
            "value": 0.0,
            "unit": "error",
            "vs_baseline": 0.0,
            "extra": {
                "error": repr(e),
                "traceback": traceback.format_exc()[-1500:],
                "tpu_probe": tpu_diags,
            },
        }
    print(json.dumps(result))


def main():
    argv = sys.argv[1:]
    if "--config" in argv:
        _child_main(argv[argv.index("--config") + 1])
        return

    # ---- parent: orchestration only, jax is never imported here ----
    _norm_moment_dtype(os.environ.get("BENCH_MOMENT_DTYPE"))  # fail fast
    env = dict(os.environ)
    if env.get("JAX_PLATFORMS", "") != "cpu":
        ok, diags = probe_tpu()
        if not ok:
            # TPU unreachable: run everything on CPU with the axon
            # plugin env scrubbed (a hung tunnel stalls even CPU-only
            # runs at plugin-registration time) and carry diagnostics.
            env.pop("PALLAS_AXON_POOL_IPS", None)
            env["JAX_PLATFORMS"] = "cpu"
            env["_BENCH_DIAGS"] = json.dumps(
                {"tpu_unavailable": True, "attempts": diags})
    else:
        # CPU was requested explicitly: scrub the tunnel plugin too, or
        # every child pays a multi-minute PJRT-init stall when the
        # tunnel is down (round-4 find: the headline child burned its
        # whole timeout inside plugin registration).
        env.pop("PALLAS_AXON_POOL_IPS", None)

    try:
        result = _run_one_config("llama", env, HEADLINE_TIMEOUT)
        if "--no-secondary" not in argv:
            result.setdefault("extra", {})["secondary"] = \
                _run_secondary_configs(env)
        _maybe_write_baseline(result)
        _apply_baseline_ratio(result)
        print(_compact_line(result))
    finally:
        _clear_heartbeat()


if __name__ == "__main__":
    main()
