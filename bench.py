"""Benchmark: Llama pretraining step on the available TPU chip(s).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Metric: tokens/sec/chip for a causal-LM train step (fwd+bwd+AdamW update,
bf16 compute / fp32 master, ZeRO-3-equivalent sharding when >1 chip).
vs_baseline: BASELINE.json has "published": {} (no reference numbers), so
this reports the ratio against our own recorded first measurement when
BENCH_BASELINE.json exists, else 1.0.
"""

from __future__ import annotations

import json
import os
import sys
import time


def main():
    import jax
    import jax.numpy as jnp
    import numpy as np

    import paddle_tpu as pt
    from paddle_tpu import amp, distributed as dist, optimizer as opt
    from paddle_tpu.distributed.strategy import (
        DistributedStrategy,
        HybridConfig,
    )
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.trainer import TrainStep

    devices = jax.devices()
    n = len(devices)
    platform = devices[0].platform

    # a ~350M-param Llama: big enough to be MXU-bound, small enough to
    # fit one v5e chip with batch tokens that saturate it
    cfg = LlamaConfig(
        vocab_size=32000,
        hidden_size=1024,
        intermediate_size=2816,
        num_hidden_layers=16,
        num_attention_heads=8,  # head_dim 128 → Pallas flash kernel
        num_key_value_heads=8,
        max_position_embeddings=2048,
        use_flash_attention=True,
        use_recompute=True,
        dtype="bfloat16",
    )
    batch, seq = 4, 2048

    pt.seed(0)
    model = LlamaForCausalLM(cfg)
    model.to(pt.bfloat16)

    optimizer = opt.AdamW(
        learning_rate=3e-4, weight_decay=0.01, multi_precision=True,
        grad_clip=opt.ClipGradByGlobalNorm(1.0),
    )
    strategy = DistributedStrategy()
    if n > 1:
        strategy.hybrid_configs = HybridConfig(sharding_degree=n)
        strategy.sharding = True
        strategy.sharding_configs.stage = 3
        mesh = dist.build_mesh(fsdp=n, devices=devices)
    else:
        mesh = dist.build_mesh(devices=devices)

    ts = TrainStep(model, optimizer, mesh, strategy)
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (batch, seq)))
    data = {"input_ids": ids, "labels": ids}

    # warmup / compile
    ts.run(data).block_until_ready()
    ts.run(data).block_until_ready()

    iters = 10
    t0 = time.perf_counter()
    loss = None
    for _ in range(iters):
        loss = ts.run(data)
    loss.block_until_ready()
    dt = time.perf_counter() - t0

    tokens_per_sec = batch * seq * iters / dt
    tokens_per_sec_chip = tokens_per_sec / n

    # MFU: 6*N_params*tokens/sec vs peak flops (v5e bf16 ~197 TF/s/chip)
    n_params = sum(
        int(np.prod(p.shape)) for p in model.parameters()
    )
    model_flops = 6 * n_params * tokens_per_sec_chip
    peak = {"tpu": 197e12, "cpu": 1e12}.get(platform, 197e12)
    mfu = model_flops / peak

    vs = 1.0
    base_path = os.path.join(os.path.dirname(__file__), "BENCH_BASELINE.json")
    if os.path.exists(base_path):
        try:
            with open(base_path) as f:
                vs = tokens_per_sec_chip / float(json.load(f)["value"])
        except Exception:
            vs = 1.0

    result = {
        "metric": "llama350m_train_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec_chip, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(vs, 3),
        "extra": {
            "n_chips": n,
            "platform": platform,
            "params": n_params,
            "batch": batch,
            "seq": seq,
            "step_ms": round(1000 * dt / iters, 2),
            "mfu_est": round(mfu, 4),
            "loss": float(loss),
        },
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
