"""Benchmark: Llama pretraining step on the available TPU chip(s).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Metric: tokens/sec/chip for a causal-LM train step (fwd+bwd+AdamW update,
bf16 compute / fp32 master, ZeRO-3-equivalent sharding when >1 chip).
vs_baseline: BASELINE.json has "published": {} (no reference numbers), so
this reports the ratio against our own recorded first measurement when
BENCH_BASELINE.json exists, else 1.0.

Resilience contract (round-1 failed rc=1 on TPU-backend init): the TPU
backend is probed in a KILLABLE SUBPROCESS with retries/backoff — a hung
or failing PJRT init can never take this process down. If the TPU is
unreachable the benchmark still emits a valid JSON line from a CPU smoke
run, with the TPU failure diagnostics in "extra.tpu_probe".

Usage:
  python bench.py            # headline: llama train step
  python bench.py --config moe|vit|mamba|infer   # secondary benchmarks
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

PROBE_TIMEOUTS = (240, 120)  # seconds per attempt; first covers cold init


def probe_tpu():
    """Try to bring up the TPU backend in a killable child. Returns
    (ok, diagnostics)."""
    code = (
        "import jax; ds = jax.devices(); "
        "import jax.numpy as jnp; "
        "x = jnp.ones((128, 128)); "
        "print((x @ x).sum()); "
        "print('PROBE_OK', len(ds), ds[0].platform)"
    )
    diags = []
    for attempt, tmo in enumerate(PROBE_TIMEOUTS):
        t0 = time.time()
        try:
            r = subprocess.run(
                [sys.executable, "-c", code],
                capture_output=True, text=True, timeout=tmo,
            )
            if r.returncode == 0 and "PROBE_OK" in r.stdout:
                return True, diags
            diags.append({
                "attempt": attempt, "rc": r.returncode,
                "elapsed_s": round(time.time() - t0, 1),
                "stderr_tail": r.stderr[-800:],
            })
        except subprocess.TimeoutExpired:
            diags.append({
                "attempt": attempt, "rc": "timeout",
                "elapsed_s": round(time.time() - t0, 1),
                "stderr_tail": f"probe hung > {tmo}s (PJRT init stall)",
            })
        if attempt < len(PROBE_TIMEOUTS) - 1:
            time.sleep(5 * (attempt + 1))
    return False, diags


def _llama_cfg(platform):
    from paddle_tpu.models import LlamaConfig

    if platform == "tpu":
        # a ~350M-param Llama: big enough to be MXU-bound, small enough
        # to fit one v5e chip with batch tokens that saturate it
        return LlamaConfig(
            vocab_size=32000,
            hidden_size=1024,
            intermediate_size=2816,
            num_hidden_layers=16,
            num_attention_heads=8,  # head_dim 128 → Pallas flash kernel
            num_key_value_heads=8,
            max_position_embeddings=2048,
            use_flash_attention=True,
            use_recompute=True,
            dtype="bfloat16",
        ), 4, 2048, 10
    # CPU smoke: tiny but same code path
    return LlamaConfig(
        vocab_size=512,
        hidden_size=256,
        intermediate_size=512,
        num_hidden_layers=2,
        num_attention_heads=2,
        num_key_value_heads=2,
        max_position_embeddings=256,
        use_flash_attention=False,
        dtype="float32",
    ), 2, 256, 3


def bench_llama_train(tpu_diags):
    import jax
    import jax.numpy as jnp
    import numpy as np

    import paddle_tpu as pt
    from paddle_tpu import distributed as dist, optimizer as opt
    from paddle_tpu.distributed.strategy import (
        DistributedStrategy,
        HybridConfig,
    )
    from paddle_tpu.models import LlamaForCausalLM
    from paddle_tpu.trainer import TrainStep

    devices = jax.devices()
    n = len(devices)
    platform = devices[0].platform

    cfg, batch, seq, iters = _llama_cfg(platform)

    pt.seed(0)
    model = LlamaForCausalLM(cfg)
    if cfg.dtype == "bfloat16":
        model.to(pt.bfloat16)

    optimizer = opt.AdamW(
        learning_rate=3e-4, weight_decay=0.01,
        multi_precision=(cfg.dtype == "bfloat16"),
        grad_clip=opt.ClipGradByGlobalNorm(1.0),
    )
    strategy = DistributedStrategy()
    if n > 1:
        strategy.hybrid_configs = HybridConfig(sharding_degree=n)
        strategy.sharding = True
        strategy.sharding_configs.stage = 3
        mesh = dist.build_mesh(fsdp=n, devices=devices)
    else:
        mesh = dist.build_mesh(devices=devices)

    ts = TrainStep(model, optimizer, mesh, strategy)
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (batch, seq)))
    data = {"input_ids": ids, "labels": ids}

    # warmup / compile
    ts.run(data).block_until_ready()
    ts.run(data).block_until_ready()

    t0 = time.perf_counter()
    loss = None
    for _ in range(iters):
        loss = ts.run(data)
    loss.block_until_ready()
    dt = time.perf_counter() - t0

    tokens_per_sec = batch * seq * iters / dt
    tokens_per_sec_chip = tokens_per_sec / n

    # MFU: 6*N_params*tokens/sec vs peak flops (v5e bf16 ~197 TF/s/chip)
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    model_flops = 6 * n_params * tokens_per_sec_chip
    peak = {"tpu": 197e12, "cpu": 1e12}.get(platform, 197e12)
    mfu = model_flops / peak

    vs = 1.0
    base_path = os.path.join(os.path.dirname(__file__), "BENCH_BASELINE.json")
    if os.path.exists(base_path) and platform == "tpu":
        try:
            with open(base_path) as f:
                vs = tokens_per_sec_chip / float(json.load(f)["value"])
        except Exception:
            vs = 1.0

    extra = {
        "n_chips": n,
        "platform": platform,
        "params": n_params,
        "batch": batch,
        "seq": seq,
        "step_ms": round(1000 * dt / iters, 2),
        "mfu_est": round(mfu, 4),
        "loss": float(loss),
    }
    if tpu_diags:
        extra["tpu_probe"] = tpu_diags
    name = ("llama350m_train_tokens_per_sec_per_chip" if platform == "tpu"
            else "llama_train_cpu_smoke_tokens_per_sec")
    return {
        "metric": name,
        "value": round(tokens_per_sec_chip, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(vs, 3),
        "extra": extra,
    }


def main():
    argv = sys.argv[1:]
    config = "llama"
    if "--config" in argv:
        config = argv[argv.index("--config") + 1]

    tpu_diags = None
    if os.environ.get("_BENCH_CHILD"):
        tpu_diags = json.loads(os.environ["_BENCH_CHILD"])
    elif os.environ.get("JAX_PLATFORMS", "") not in ("", "cpu"):
        ok, diags = probe_tpu()
        if not ok:
            # Fall back to CPU in a RE-EXEC'D child with the axon plugin
            # env scrubbed: this interpreter already registered the
            # tunnel plugin via sitecustomize, and jax initializes every
            # registered plugin on first use — a hung tunnel would block
            # even a CPU-only run in-process.
            env = dict(os.environ)
            env.pop("PALLAS_AXON_POOL_IPS", None)
            env["JAX_PLATFORMS"] = "cpu"
            env["_BENCH_CHILD"] = json.dumps(
                {"tpu_unavailable": True, "attempts": diags})
            try:
                r = subprocess.run(
                    [sys.executable, os.path.abspath(__file__)] + argv,
                    env=env, timeout=1800, capture_output=True, text=True,
                )
                out = r.stdout.strip().splitlines()
                print(out[-1] if out else json.dumps({
                    "metric": f"bench_{config}_failed", "value": 0.0,
                    "unit": "error", "vs_baseline": 0.0,
                    "extra": {"stderr": r.stderr[-1000:]}}))
            except subprocess.TimeoutExpired:
                print(json.dumps({
                    "metric": f"bench_{config}_failed", "value": 0.0,
                    "unit": "error", "vs_baseline": 0.0,
                    "extra": {"error": "cpu fallback bench timed out"}}))
            return

    try:
        if config == "llama":
            result = bench_llama_train(tpu_diags)
        else:
            from benchmarks.suite import run_config

            result = run_config(config, tpu_diags)
    except Exception as e:  # last-resort: never exit nonzero silently
        import traceback

        result = {
            "metric": f"bench_{config}_failed",
            "value": 0.0,
            "unit": "error",
            "vs_baseline": 0.0,
            "extra": {
                "error": repr(e),
                "traceback": traceback.format_exc()[-1500:],
                "tpu_probe": tpu_diags,
            },
        }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
